"""Multi-process FCMA voxel selection — the ``mpirun`` launcher analog.

The reference distributes FCMA with ``mpirun -np N python3
voxel_selection.py ...`` (reference examples/fcma/run_voxel_selection.sh);
here the script IS the launcher: run

    python examples/distributed_fcma.py --processes 2

and it re-executes itself as N OS processes that form a
``jax.distributed`` cluster (a local coordinator standing in for a TPU
pod's control plane), each process backed by ``--devices-per-process``
virtual CPU devices.  The global mesh spans every device in the
cluster; ``VoxelSelector`` shards the voxel axis across it and every
process prints the same gathered top voxels — on real multi-host TPU
the launch is identical except each host runs one process and
``jax.distributed.initialize`` discovers the pod (see
brainiak_tpu.parallel.mesh.initialize_distributed).
"""

import argparse
import math
import os
import socket
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def make_data(n_epochs=8, n_trs=20, n_voxels=32, seed=5):
    import numpy as np
    rng = np.random.RandomState(seed)
    raw = []
    for _ in range(n_epochs):
        mat = rng.randn(n_trs, n_voxels).astype(np.float64)
        mat = (mat - mat.mean(0)) / (mat.std(0) * math.sqrt(n_trs))
        raw.append(mat)
    return raw, [0, 1] * (n_epochs // 2)


def worker(args):
    import re
    # append-preserving: keep any user XLA flags, override only the
    # virtual device count
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count="
        f"{args.devices_per_process}").strip()
    import jax

    if args.backend == "cpu":
        jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=args.coordinator,
                               num_processes=args.processes,
                               process_id=args.process_id)
    import numpy as np
    from jax.sharding import Mesh

    from brainiak_tpu.fcma.voxelselector import VoxelSelector

    mesh = Mesh(np.array(jax.devices()), ("voxel",))
    raw, labels = make_data()
    vs = VoxelSelector(labels, len(labels) // 2, 2, raw, voxel_unit=8,
                       mesh=mesh, use_pallas=False)
    results = vs.run('svm')  # gathered on every process
    header = (f"[process {args.process_id}/{args.processes}, "
              f"{jax.process_count()} processes x "
              f"{jax.local_device_count()} devices] top voxels:")
    # one atomic write per process so concurrent stdout cannot
    # interleave between this process's lines
    block = "\n".join([header] + [
        f"  voxel {voxel_id:3d}  accuracy {accuracy:.3f}"
        for voxel_id, accuracy in results[:args.top]]) + "\n"
    sys.stdout.write(block)
    sys.stdout.flush()


def launcher(args, timeout=300):
    import time
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"
    procs = []
    for pid in range(args.processes):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--processes", str(args.processes),
               "--devices-per-process", str(args.devices_per_process),
               "--backend", args.backend, "--top", str(args.top),
               "--coordinator", coordinator, "--process-id", str(pid)]
        procs.append(subprocess.Popen(cmd))
    # poll rather than wait sequentially: the moment any worker fails,
    # kill the rest — peers blocked in a collective would otherwise
    # hang to the timeout (same rationale as
    # brainiak_tpu/parallel/testing.py:run_distributed)
    deadline = time.monotonic() + timeout
    try:
        while True:
            rcs = [p.poll() for p in procs]
            if all(rc == 0 for rc in rcs):
                return
            if any(rc not in (None, 0) for rc in rcs):
                raise SystemExit(f"worker exit codes: {rcs}")
            if time.monotonic() > deadline:
                raise SystemExit(f"timed out after {timeout}s; "
                                 f"exit codes so far: {rcs}")
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--processes", type=int, default=2)
    ap.add_argument("--devices-per-process", type=int, default=2)
    ap.add_argument("--backend", default="cpu",
                    help="cpu (default) keeps the demo off any "
                         "ambient accelerator")
    ap.add_argument("--top", type=int, default=5)
    ap.add_argument("--coordinator", default=None,
                    help="internal: set when running as a worker")
    ap.add_argument("--process-id", dest="process_id", type=int,
                    default=None)
    args = ap.parse_args()
    if args.coordinator is None:
        launcher(args)
    else:
        try:
            worker(args)
        except BaseException:
            import traceback
            traceback.print_exc(file=sys.stderr)
            sys.stderr.flush()
            # skip atexit: jax.distributed shutdown would block on
            # peers that are themselves stuck in a collective waiting
            # for this process (same guard as parallel/testing.py)
            os._exit(1)


if __name__ == "__main__":
    main()
